"""Native (C++) runtime components, built lazily with the system toolchain.

Two libraries (see the .cpp files for the design notes):
- libceph_tpu_gf:    GF(2^8) SIMD region kernels (the missing isa-l /
                     gf-complete role) — backs the "native" EC engine.
- libceph_tpu_crush: threaded batch CRUSH mapper (the ParallelPGMapper
                     role) — the fast host backend for the CLIs.

Both are optional: if no C++ compiler is available the callers fall back to
the numpy / Python paths.  Build artifacts are cached in
ceph_tpu/native/build/ (gitignored).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
from pathlib import Path

HERE = Path(__file__).resolve().parent
BUILD = HERE / "build"

_cache: dict[str, ctypes.CDLL | None] = {}


def _compile(name: str, src: Path, extra: list[str]) -> Path | None:
    so = BUILD / f"lib{name}.so"
    if so.exists() and so.stat().st_mtime >= src.stat().st_mtime:
        return so
    BUILD.mkdir(exist_ok=True)
    cxx = os.environ.get("CXX", "g++")
    cmd = [
        cxx, "-O3", "-std=c++17", "-fPIC", "-shared",
        *extra, str(src), "-o", str(so),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError):
        return None
    return so


def _load(name: str, src: str, extra: list[str]) -> ctypes.CDLL | None:
    if name in _cache:
        return _cache[name]
    so = _compile(name, HERE / src, extra)
    lib = ctypes.CDLL(str(so)) if so else None
    _cache[name] = lib
    return lib


def _native_march_flags() -> list[str]:
    # -march=native gives the SIMD paths; fall back if unsupported
    return ["-march=native"]


def load_gf() -> ctypes.CDLL | None:
    lib = _load("ceph_tpu_gf", "gf.cpp", _native_march_flags())
    if lib is None:
        lib = _load("ceph_tpu_gf_plain", "gf.cpp", [])
    if lib is None:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.gf_native_simd_level.restype = ctypes.c_int
    lib.gf_native_matvec.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, u8p, u8p, ctypes.c_longlong,
    ]
    lib.gf_native_mul_region.argtypes = [
        ctypes.c_int, u8p, u8p, ctypes.c_longlong, ctypes.c_int,
    ]
    return lib


def load_crc() -> ctypes.CDLL | None:
    lib = _load("ceph_tpu_crc", "crc.cpp", [])
    if lib is None:
        return None
    lib.ceph_tpu_crc32c.restype = ctypes.c_uint32
    lib.ceph_tpu_crc32c.argtypes = [
        ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.ceph_tpu_crc32c_hw.restype = ctypes.c_int
    return lib


def load_crush() -> ctypes.CDLL | None:
    lib = _load("ceph_tpu_crush", "crush.cpp", ["-pthread"])
    if lib is None:
        return None
    ip = ctypes.POINTER(ctypes.c_int)
    up = ctypes.POINTER(ctypes.c_uint)
    llp = ctypes.POINTER(ctypes.c_longlong)
    lib.cm_set_ln_tables.argtypes = [llp, llp]
    lib.cm_create.restype = ctypes.c_void_p
    lib.cm_create.argtypes = [ctypes.c_int] * 6
    lib.cm_add_bucket.restype = ctypes.c_int
    lib.cm_add_bucket.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ip, ip, ip, ip, ctypes.c_int, ip,
    ]
    lib.cm_add_rule.restype = ctypes.c_int
    lib.cm_add_rule.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ip, ip, ip,
    ]
    lib.cm_set_choose_args.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, up, ip, ctypes.c_int,
    ]
    lib.cm_set_max_devices.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.cm_map_batch.restype = ctypes.c_longlong
    lib.cm_map_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int, up, ctypes.c_longlong, ctypes.c_int,
        up, ctypes.c_int, ip, ctypes.c_int, ctypes.c_int,
    ]
    lib.cm_destroy.argtypes = [ctypes.c_void_p]

    # inject the fixed-point log tables once
    import numpy as np

    from ceph_tpu.core.lntable import LL_TBL, RH_LH_TBL

    rh = np.ascontiguousarray(RH_LH_TBL, dtype=np.int64)
    ll = np.ascontiguousarray(LL_TBL, dtype=np.int64)
    lib.cm_set_ln_tables(
        rh.ctypes.data_as(llp), ll.ctypes.data_as(llp)
    )
    lib._ln_keepalive = (rh, ll)
    return lib
