"""Python wrapper over the native threaded CRUSH mapper."""

from __future__ import annotations

import ctypes

import numpy as np

from ceph_tpu.crush.types import CrushMap, ITEM_NONE
from ceph_tpu.native import load_crush

_IP = ctypes.POINTER(ctypes.c_int)
_UP = ctypes.POINTER(ctypes.c_uint)


def available() -> bool:
    return load_crush() is not None


class NativeMapper:
    """Mirror a CrushMap into the C++ engine; map batches across threads.

    The native analogue of PoolMapper's rule kernel: same semantics as
    ceph_tpu.crush.mapper_ref.do_rule (differentially tested), used as the
    multicore host backend and CPU baseline.
    """

    def __init__(self, m: CrushMap, choose_args=None):
        lib = load_crush()
        if lib is None:
            raise RuntimeError("native crush library unavailable")
        self.lib = lib
        t = m.tunables
        self.h = lib.cm_create(
            t.choose_local_tries, t.choose_local_fallback_tries,
            t.choose_total_tries, t.chooseleaf_descend_once,
            t.chooseleaf_vary_r, t.chooseleaf_stable,
        )
        for bid in sorted(m.buckets, reverse=True):
            b = m.buckets[bid]
            n = b.size

            def arr(vals):
                if vals is None:
                    return None
                a = (ctypes.c_int * len(vals))(*[int(v) for v in vals])
                return ctypes.cast(a, _IP)

            nodes = b.node_weights
            lib.cm_add_bucket(
                self.h, bid, int(b.alg), b.type, n,
                arr(b.items), arr(b.weights), arr(b.sum_weights),
                arr(nodes), len(nodes) if nodes else 0, arr(b.straws),
            )
        for ruleno, r in enumerate(m.rules):
            if r is None:
                continue
            ns = len(r.steps)
            ops = (ctypes.c_int * ns)(*[int(op) for op, _, _ in r.steps])
            a1 = (ctypes.c_int * ns)(*[a for _, a, _ in r.steps])
            a2 = (ctypes.c_int * ns)(*[a for _, _, a in r.steps])
            lib.cm_add_rule(
                self.h, ruleno, r.ruleset, r.type, r.min_size, r.max_size,
                ns, ctypes.cast(ops, _IP), ctypes.cast(a1, _IP),
                ctypes.cast(a2, _IP),
            )
        lib.cm_set_max_devices(self.h, m.max_devices)
        # mirror one ChooseArgs set (per-bucket weight-set overrides)
        self.has_choose_args = False
        if choose_args is not None:
            for bid, ws in choose_args.weight_sets.items():
                b = m.buckets.get(bid)
                if b is None or not ws:
                    continue
                ids = choose_args.ids.get(bid)
                # the C side slices flat buffers at bucket-size strides:
                # reject mismatched rows instead of feeding it garbage
                if any(len(row) != b.size for row in ws) or (
                    ids is not None and len(ids) != b.size
                ):
                    raise ValueError(
                        f"choose_args for bucket {bid}: weight rows/ids "
                        f"must have exactly {b.size} entries"
                    )
                positions = len(ws)
                flat = [int(w) for row in ws for w in row]
                wa = (ctypes.c_uint * len(flat))(*flat)
                ia = (
                    ctypes.cast(
                        (ctypes.c_int * len(ids))(*ids), _IP
                    )
                    if ids
                    else None
                )
                lib.cm_set_choose_args(
                    self.h, bid, positions, ctypes.cast(wa, _UP), ia,
                    b.size,
                )
                self.has_choose_args = True

    def map_batch(
        self,
        ruleno: int,
        xs: np.ndarray,
        result_max: int,
        weights: list[int] | np.ndarray,
        n_threads: int = 0,
    ) -> np.ndarray:
        """-> int32[n, result_max], ITEM_NONE padded."""
        xs = np.ascontiguousarray(xs, dtype=np.uint32)
        w = np.ascontiguousarray(weights, dtype=np.uint32)
        out = np.full((len(xs), result_max), ITEM_NONE, np.int32)
        self.lib.cm_map_batch(
            self.h, ruleno,
            xs.ctypes.data_as(_UP), len(xs), result_max,
            w.ctypes.data_as(_UP), len(w),
            out.ctypes.data_as(_IP), n_threads,
            1 if self.has_choose_args else 0,
        )
        return out

    def __del__(self):
        try:
            self.lib.cm_destroy(self.h)
        except Exception:
            pass
