"""daemon — the `ceph daemon <name> <command>` surface for this port.

The reference queries a live daemon's internals over its admin socket
(`ceph daemon osd.0 perf dump`, reference src/common/admin_socket.cc);
the same commands work here in two modes:

    # against a LIVE process (started with CEPH_TPU_ADMIN_SOCKET=/p/x.asok):
    python -m ceph_tpu.cli.daemon --sock /p/x.asok perf dump

    # in-process: run a small self-test workload (pipeline mapping + an
    # RS(8,4) encode) to populate the registry, then execute the command:
    python -m ceph_tpu.cli.daemon perf dump

Commands (reference names):

    perf dump     perf-dump JSON (u64 bare, avg/time_avg avgcount+sum,
                  histogram bounds+buckets, quantile + p50/p90/p99) plus
                  the `executables` compile-cache registry section
    perf schema   kind + description per counter
    perf reset    zero every counter, keep declarations
    metrics       Prometheus text exposition (format 0.0.4)
    cache dump    executable registry with JAX cost/memory analysis
                  (flops, bytes accessed, peak temp memory, rooflines)
    bad dump      placement-diagnostics snapshots (per-source bad
                  mappings, retry histograms; ceph_tpu.obs.placement)
    explain X.Y   host-oracle decision log for PG Y of pool X (the
                  crushtool-explain replay, served for mapped pools)
    trace flush   write the Chrome trace-event file (CEPH_TPU_TRACE)
    runtime       backend-acquisition provenance (ceph_tpu.runtime:
                  backend, fallback_reason, attempts) + armed faults
    serve status  live placement-service status (ceph_tpu.serve:
                  epoch, queue depth, shed/degraded counters,
                  swap-stall tail)
    health        summarized HEALTH_OK/WARN/ERR + raised checks
                  (ceph_tpu.obs.health; the `ceph status` analogue)
    timeline dump every recorded timeline series (obs/timeline.py),
                  both retention tiers, chronological
    help          command list

The in-process self-test pins JAX to CPU (it is a diagnostic path — it
must answer in seconds even when the accelerator is wedged, which is
exactly when you reach for it); pass `--no-selftest` to skip the
workload and dump whatever this process has, or `--sock` to inspect a
real run on whatever device it owns.
"""

from __future__ import annotations

import argparse
import os
import sys

from ceph_tpu.utils.dout import subsys_logger

log = subsys_logger("obs")


def _import_obs_without_serving():
    """A one-shot diagnostic CLI never serves the admin socket itself —
    an inherited CEPH_TPU_ADMIN_SOCKET would otherwise race the live
    process this tool is querying (obs starts the server at first
    import).  The env var is hidden only for the import, then restored:
    importing this module must not mutate the process environment."""
    saved = os.environ.pop("CEPH_TPU_ADMIN_SOCKET", None)
    try:
        from ceph_tpu.obs import admin_socket
    finally:
        if saved is not None:
            os.environ["CEPH_TPU_ADMIN_SOCKET"] = saved
    return admin_socket


SELFTEST_PGS = 256
SELFTEST_OSDS = 16


def _selftest() -> None:
    """A small mapping run + RS(8,4) encode so every hot-path counter
    group (pipeline, ec) exists and has advanced."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from ceph_tpu import obs
    from ceph_tpu.ec.registry import create_erasure_code
    from ceph_tpu.osd.osdmap import build_hierarchical
    from ceph_tpu.osd.pipeline_jax import PoolMapper
    from ceph_tpu.osd.types import PgPool, PoolType

    with obs.span("daemon.selftest"):
        pool = PgPool(
            type=PoolType.REPLICATED, size=3, crush_rule=0,
            pg_num=SELFTEST_PGS, pgp_num=SELFTEST_PGS,
        )
        # 4 hosts so size-3 chooseleaf lanes resolve inside the fast
        # window — `bad dump` then shows a real tries histogram instead
        # of the all-flagged 2-host degenerate case
        m = build_hierarchical(SELFTEST_OSDS // 4, 4, n_rack=1, pool=pool)
        pm = PoolMapper(m, 0, overlays=False)
        pm.map_batch(np.arange(SELFTEST_PGS, dtype=np.uint32))
        pm.diagnose()  # populates `bad dump` + the explain registry
        log(5, f"selftest: mapped {SELFTEST_PGS} pgs")

        rs = create_erasure_code({"plugin": "jax", "k": "8", "m": "4"})
        data = np.arange(8 * 4096, dtype=np.uint8).reshape(8, 4096)
        rs.encode_chunks(data)
        log(5, "selftest: RS(8,4) encode done")


def main(argv: list[str] | None = None) -> int:
    asok = _import_obs_without_serving()
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.cli.daemon",
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument(
        "--sock", metavar="PATH",
        help="admin socket of a live process (CEPH_TPU_ADMIN_SOCKET); "
        "default is in-process execution",
    )
    ap.add_argument(
        "--no-selftest", action="store_true",
        help="in-process mode: skip the counter-populating workload",
    )
    ap.add_argument(
        "command", nargs="+",
        help=f"one of: {', '.join(repr(c) for c in asok.COMMANDS)}",
    )
    args = ap.parse_args(argv)
    cmd = " ".join(args.command)

    if args.sock:
        try:
            out = asok.client_command(args.sock, cmd)
        except OSError as e:
            print(f"daemon: cannot reach {args.sock}: {e}", file=sys.stderr)
            return 1
        print(out)
        return 0

    # read-only commands benefit from a populated registry; mutating or
    # metadata commands run against the process as-is
    if ((cmd in ("perf dump", "perf schema", "metrics", "cache dump",
                 "bad dump") or cmd.startswith("explain"))
            and not args.no_selftest):
        _selftest()
    print(asok.handle_command(cmd))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
