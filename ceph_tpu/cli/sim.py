"""sim — drive the cluster-lifetime chaos simulator from the shell.

    python -m ceph_tpu.cli.sim run [--scenario SPEC] [--epochs N]
        [--backend jax|ref] [--checkpoint PATH] [--resume]
        [--stop-after N] [--json]
    python -m ceph_tpu.cli.sim digest [--scenario SPEC] ...

`run` evolves one cluster through the scenario's epochs (see
`ceph_tpu.sim.lifetime` for the scenario syntax), printing a summary —
or, with `--json`, the full machine-readable run record on one line.
Exit status: 0 clean, 1 when any epoch invariant was violated.

`digest` runs the same engine but prints only the final trajectory
digest — the bit-identical-replay witness two runs (or a killed run
plus `--resume`) are compared by.

Crash safety: with `--checkpoint`, state flushes atomically every
`checkpoint_every` epochs; after a kill (or an armed
`CEPH_TPU_FAULTS="lifetime_step.<epoch>=exit:9"`), re-running with
`--resume` continues from the checkpointed epoch and must land on the
same final digest an uninterrupted run produces.
"""

from __future__ import annotations

import argparse
import json
import sys

from ceph_tpu.sim.lifetime import LifetimeSim, Scenario


def _parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.cli.sim",
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument("cmd", choices=("run", "digest"))
    ap.add_argument("--scenario", default=None,
                    help="comma-separated key=value scenario overrides "
                         "(ceph_tpu.sim.lifetime.Scenario fields)")
    ap.add_argument("--epochs", type=int, default=None,
                    help="override the scenario's epoch count")
    ap.add_argument("--backend", default="jax", choices=("jax", "ref"),
                    help="device accounting (jax, host-degradable) or "
                         "host-only (ref)")
    ap.add_argument("--checkpoint", default=None,
                    help="atomic state file for crash-safe runs")
    ap.add_argument("--resume", action="store_true",
                    help="continue from --checkpoint's last state")
    ap.add_argument("--stop-after", type=int, default=None,
                    help="stop after this epoch (checkpoint + exit; "
                         "the resume test's controlled interrupt)")
    ap.add_argument("--json", action="store_true",
                    help="print the full run record as one JSON line")
    return ap


def main(argv: list[str] | None = None) -> int:
    args = _parser().parse_args(argv)
    if args.resume and not args.checkpoint:
        print("--resume needs --checkpoint", file=sys.stderr)
        return 2
    spec = args.scenario
    if args.resume and spec is None:
        # resume without --scenario adopts the checkpoint's pinned
        # scenario (the README flow); a missing/fresh checkpoint just
        # falls back to defaults, exactly like a non-resume run
        try:
            state = json.loads(
                open(args.checkpoint).read()).get("lifetime") or {}
            spec = state.get("scenario")
        except (OSError, ValueError):
            pass
    sc = Scenario.parse(spec)
    if args.epochs is not None:
        sc.epochs = args.epochs
    sim = LifetimeSim(sc, backend=args.backend,
                      checkpoint=args.checkpoint, resume=args.resume)
    out = sim.run(stop_after=args.stop_after)
    if args.cmd == "digest":
        print(out["digest"])
    elif args.json:
        print(json.dumps(out))
    else:
        prov = out["provenance"]
        print(f"epochs          {out['epochs']} "
              f"(map epoch {out['map_epoch']})")
        print(f"digest          {out['digest']}")
        print(f"sim time        {out['sim_seconds']:.0f}s "
              f"({out['sim_years']:.4f} cluster-years)")
        print(f"rate            {out['epochs_per_sec']} epochs/s, "
              f"{out['cluster_years_per_hour']} cluster-years/hour")
        print(f"events          {out['events']}")
        print(f"movement        {out['report']}")
        print(f"degraded epochs {out['degraded_epochs']}")
        h = out.get("health")
        if h:
            ep = h.get("epochs") or {}
            codes = ",".join(sorted(h.get("checks") or ())) or "-"
            print(f"health          {h['status']} (epochs: "
                  f"{ep.get('ok', 0)} ok / {ep.get('warn', 0)} warn / "
                  f"{ep.get('err', 0)} err; raised: {codes}; "
                  f"{h.get('timeline_samples', 0)} timeline samples)")
        rec = out.get("recovery")
        if rec:
            print(f"recovery        queue: {rec['enqueued_gb']} GB "
                  f"enqueued, {rec['drained_gb']} drained, "
                  f"{rec['backlog_gb']} backlog "
                  f"(peak {rec['backlog_peak_gb']}), "
                  f"{rec['completed_pgs']} PG recoveries, "
                  f"{rec['conservation_violations']} conservation "
                  f"violation(s)")
        else:
            print(f"recovery        {out['recovery_model']}")
        wl = out.get("workload")
        if wl:
            print(f"workload        {wl['requests']} requests "
                  f"({wl['served_qps']} QPS): "
                  f"{wl['degraded_reads']} degraded reads, "
                  f"{wl['at_risk_hits']} at-risk hits, "
                  f"{wl['backlog_hits']} backlog hits, "
                  f"{wl['contended_osd_epochs']} contended OSD-epochs")
        ch = out.get("chaos")
        if ch:
            # the correlated-chaos triage table: worst failure domains,
            # the cascade record, and the repeat offenders — readable
            # without parsing the digest log
            print(f"chaos           {ch['cascades']} cascade(s) "
                  f"(longest {ch['longest_cascade']}), "
                  f"{ch['hazard_windows']} hazard window(s), "
                  f"{ch['false_flap_revives']} false-flap revive(s)")
            if ch.get("domain_outages"):
                print("  domain outages:")
                for name, cnt in ch["domain_outages"].items():
                    print(f"    {name:<12} {cnt}")
            if ch.get("flap_counts"):
                print("  flap offenders (designated flappers: "
                      + ",".join(f"osd.{o}"
                                 for o in ch["flapper_osds"]) + "):")
                for name, cnt in ch["flap_counts"].items():
                    print(f"    {name:<12} {cnt}")
        dur = out.get("durability")
        if dur:
            print(f"durability      pg_lost {dur['pg_lost']}, "
                  f"{dur['exposed_pg_epochs']} exposed PG-epochs, "
                  f"{dur['wounded_pgs']} wounded PG(s) "
                  f"(max {dur['max_wounds']} dead chunks)")
            for pid, pgs in (dur.get("lost") or {}).items():
                print(f"  LOST pool {pid}: pgs {pgs}")
        if out.get("pareto"):
            print(f"pareto          {out['pareto']}")
        print(f"trace-once      {out['trace_once']}")
        print(f"backend         {prov['backend']} "
              f"({prov['device_loss_fallbacks']} device-loss "
              f"degradations)")
        print(f"invariants      {out['invariant_violations']} "
              f"violation(s)")
        for v in out["violations"]:
            print(f"  VIOLATION {v}")
    return 1 if out["invariant_violations"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
