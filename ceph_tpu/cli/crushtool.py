"""crushtool — flag-compatible CLI over the text compiler + tester.

Covers the reference tool's compile/decompile/build/test surface
(reference src/tools/crushtool.cc:129-231 usage, :436-1276 arg loop):

    crushtool -c map.txt -o map        compile to the wire-format binary
    crushtool -d map [-o out.txt]      decompile (binary or text input)
    crushtool --build --num_osds N layer1 alg size ...
    crushtool -i map --test [--min-x --max-x --num-rep --rule --pool-id
                             --weight osd w --show-statistics
                             --show-utilization[-all] --show-mappings
                             --show-bad-mappings --show-choose-tries
                             --simulate --backend jax|ref]
    crushtool -i map --tree
    crushtool -i map --reweight-item name w -o out

Extra (this framework):

    --backend jax|ref          vmapped TPU kernel (default) or the
                               pure-Python host mapper
    crushtool -i map explain <x>
                               replay ONE placement through the
                               instrumented host oracle: bucket
                               descents, straw2 draw winners/losers,
                               rejection reasons, per-step work vectors
                               (honors --rule/--num-rep/--pool-id/-w;
                               <x> may also be <pool>.<seed>, which
                               sets --pool-id)
    crushtool -i map --locate-divergence [--against other-map]
                               run min-x..max-x through BOTH the
                               device kernel (built from -i map) and
                               the host oracle (walking --against, or
                               the same map) and report the earliest
                               choose step where they disagree — the
                               jax-vs-host triage entry point
"""

from __future__ import annotations

import sys

from ceph_tpu.crush.codec import (
    decode_crushmap,
    encode_crushmap,
    looks_like_crushmap,
)
from ceph_tpu.crush.compiler import compile_text, decompile
from ceph_tpu.crush.tester import CrushTester, TesterConfig
from ceph_tpu.crush.types import BucketAlg, CrushMap
from ceph_tpu.osd.osdmap import DEFAULT_TYPES


def _read_map(path: str) -> CrushMap:
    """Binary (wire format) or text, auto-detected like the real tool."""
    with open(path, "rb") as f:
        data = f.read()
    if looks_like_crushmap(data):
        return decode_crushmap(data)
    return compile_text(data.decode())


def _write(path: str | None, text: str) -> None:
    if path is None or path == "-":
        sys.stdout.write(text)
    else:
        with open(path, "w") as f:
            f.write(text)


def _write_map(path: str, m: CrushMap) -> None:
    """One suffix policy everywhere: .txt -> decompiled text, else the
    wire-format binary (what the reference tool emits)."""
    if path.endswith(".txt"):
        _write(path, decompile(m))
    else:
        with open(path, "wb") as f:
            f.write(encode_crushmap(m))


_ALGS = {
    "uniform": BucketAlg.UNIFORM,
    "list": BucketAlg.LIST,
    "tree": BucketAlg.TREE,
    "straw": BucketAlg.STRAW,
    "straw2": BucketAlg.STRAW2,
}


def build_map(num_osds: int, layers: list[tuple[str, str, int]]) -> CrushMap:
    """--build: stack layers bottom-up (reference crushtool.cc:731-919
    semantics: each layer groups `size` children of the previous layer into
    buckets of `alg`; size 0 = one bucket holding everything)."""
    m = CrushMap()
    m.type_names = dict(DEFAULT_TYPES)
    prev: list[tuple[int, int]] = [(i, 0x10000) for i in range(num_osds)]
    for i in range(num_osds):
        m.item_names[i] = f"osd.{i}"
    type_id = 0
    for lname, alg_name, size in layers:
        type_id += 1
        # register the layer name as a type if it isn't a default one
        if lname not in m.type_names.values():
            m.type_names[type_id] = lname
        else:
            type_id = next(
                t for t, n in m.type_names.items() if n == lname
            )
        alg = _ALGS[alg_name]
        groups: list[list[tuple[int, int]]] = []
        if size == 0:
            groups = [prev]
        else:
            for j in range(0, len(prev), size):
                groups.append(prev[j : j + size])
        new_prev = []
        for gi, g in enumerate(groups):
            name = f"{lname}{gi}" if len(groups) > 1 else lname
            bid = m.add_bucket(
                alg,
                type_id,
                [it for it, _ in g],
                [w for _, w in g],
                name=name,
            )
            new_prev.append((bid, sum(w for _, w in g)))
        prev = new_prev
    # default rule over failure-domain type 1, like the reference's
    # build path (crushtool.cc:1043 -> OSDMap::build_simple_crush_rules)
    if prev and prev[0][0] < 0:
        ruleno = m.make_replicated_rule(prev[0][0], failure_domain_type=1)
        m.rule_names[ruleno] = "replicated_rule"
    return m


def print_tree(m: CrushMap, out=sys.stdout) -> None:
    roots = set(m.buckets)
    shadow = {
        sid for per in m.class_bucket.values() for sid in per.values()
    }
    for b in m.buckets.values():
        for it in b.items:
            roots.discard(it)

    def walk(item: int, depth: int, weight: int | None):
        name = m.item_names.get(
            item, f"osd.{item}" if item >= 0 else f"bucket{-1-item}"
        )
        b = m.buckets.get(item)
        w = weight if weight is not None else (b.weight if b else 0x10000)
        kind = m.type_names.get(b.type, "bucket") if b else "osd"
        print(
            f"{'  ' * depth}{item}\t{w / 0x10000:.5f}\t{kind} {name}",
            file=out,
        )
        if b:
            for it, iw in zip(b.items, b.weights):
                walk(it, depth + 1, iw)

    print("ID\tWEIGHT\tTYPE NAME", file=out)
    for r in sorted(roots - shadow, reverse=True):
        walk(r, 0, None)


def _pick_rule(m: CrushMap, cfg: TesterConfig) -> tuple[int, int]:
    """(ruleno, numrep) for the single-placement commands: --rule wins,
    else the first present rule; --num-rep wins, else the rule's
    max_size (the tester's default numrep sweep upper bound)."""
    ruleno = (
        cfg.rule
        if cfg.rule >= 0
        else next((i for i, r in enumerate(m.rules) if r is not None), -1)
    )
    if not (0 <= ruleno < len(m.rules)) or m.rules[ruleno] is None:
        raise SystemExit(f"rule {ruleno} dne")
    nr = cfg.num_rep if cfg.num_rep >= 0 else m.rules[ruleno].max_size
    return ruleno, nr


def run_explain(m: CrushMap, cfg: TesterConfig, explain_x: str,
                out=None) -> int:
    """`crushtool -i map explain <x>`: replay one placement through the
    instrumented host oracle and print the decision log."""
    import numpy as np

    from ceph_tpu.crush import explain as explain_mod

    out = out if out is not None else sys.stdout
    if "." in explain_x:
        p, s = explain_x.split(".", 1)
        cfg.pool_id, x = int(p), int(s)
    else:
        x = int(explain_x)
    tester = CrushTester(m, cfg, out=out)
    ruleno, nr = _pick_rule(m, cfg)
    real_x = int(tester._real_xs(np.array([x], np.int64))[0])
    ex = explain_mod.explain_seed(m, ruleno, real_x, nr, tester.weight)
    if cfg.pool_id != -1:
        ex.update(pool=cfg.pool_id, seed=x, pps=real_x,
                  up=ex["result"], up_primary=(ex["result"] or [-1])[0])
    out.write(explain_mod.render_text(ex, m.item_names))
    return 0


def run_divergence(m: CrushMap, cfg: TesterConfig,
                   against_fn: str | None, out=None) -> int:
    """`crushtool -i map --locate-divergence [--against other]`: device
    kernel (from `m`) vs host oracle (walking `against`, default `m`)
    over min-x..max-x; report the earliest differing choose step.
    Returns 0 when every step agrees, 2 on a located divergence."""
    import numpy as np

    from ceph_tpu.utils import ensure_jax_backend

    ensure_jax_backend()
    from ceph_tpu.crush import explain as explain_mod

    out = out if out is not None else sys.stdout
    tester = CrushTester(m, cfg, out=out)
    ruleno, nr = _pick_rule(m, cfg)
    xs = tester._real_xs(
        np.arange(cfg.min_x, cfg.max_x + 1, dtype=np.int64)
    )
    m_host = _read_map(against_fn) if against_fn else m
    d = explain_mod.first_divergence(
        m_host, tester.m_arrays(), ruleno, xs, nr, tester.weight
    )
    span = f"rule {ruleno} x {cfg.min_x}..{cfg.max_x} numrep {nr}"
    if d is None:
        print(f"no divergence: {span} agrees step-for-step", file=out)
        return 0
    print(f"DIVERGENCE: {span}", file=out)
    print(
        f"  first differing choose step: {d['step']} at x={d['x']} "
        f"(batch index {d['batch_index']})",
        file=out,
    )
    print(f"  jax:  {d['jax']}", file=out)
    print(f"  host: {d['host']}", file=out)
    print(
        f"  {d['n_divergent']}/{d['n_checked']} seeds diverge "
        f"({d['n_unresolved_skipped']} unresolved lanes host-rescued, "
        "not compared)",
        file=out,
    )
    print("host decision log for that seed:", file=out)
    out.write(explain_mod.render_text(d["host_log"], m_host.item_names))
    return 2


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    infn = None
    outfn = None
    compilefn = None
    decompilefn = None
    do_test = False
    do_tree = False
    do_build = False
    explain_x: str | None = None
    do_divergence = False
    against_fn: str | None = None
    num_osds = 0
    layers: list[tuple[str, str, int]] = []
    cfg = TesterConfig()
    reweights: list[tuple[str, float]] = []

    i = 0

    def next_arg(what: str) -> str:
        nonlocal i
        i += 1
        if i >= len(args):
            print(f"missing argument for {what}", file=sys.stderr)
            raise SystemExit(1)
        return args[i]

    while i < len(args):
        a = args[i]
        if a in ("-i", "--infn"):
            infn = next_arg(a)
        elif a in ("-o", "--outfn"):
            outfn = next_arg(a)
        elif a in ("-c", "--compile"):
            compilefn = next_arg(a)
        elif a in ("-d", "--decompile"):
            decompilefn = next_arg(a)
        elif a == "--test":
            do_test = True
        elif a == "explain":
            explain_x = next_arg(a)
        elif a == "--locate-divergence":
            do_divergence = True
        elif a == "--against":
            against_fn = next_arg(a)
        elif a == "--tree":
            do_tree = True
        elif a == "--build":
            do_build = True
        elif a == "--num_osds":
            num_osds = int(next_arg(a))
        elif a == "--min-x":
            cfg.min_x = int(next_arg(a))
        elif a == "--max-x":
            cfg.max_x = int(next_arg(a))
        elif a == "--x":
            cfg.min_x = cfg.max_x = int(next_arg(a))
        elif a == "--num-rep":
            cfg.num_rep = int(next_arg(a))
        elif a == "--min-rep":
            cfg.min_rep = int(next_arg(a))
        elif a == "--max-rep":
            cfg.max_rep = int(next_arg(a))
        elif a == "--rule":
            cfg.rule = int(next_arg(a))
        elif a == "--pool-id":
            cfg.pool_id = int(next_arg(a))
        elif a in ("-w", "--weight"):
            osd = int(next_arg(a))
            w = float(next_arg(a))
            cfg.weights[osd] = int(w * 0x10000)
        elif a == "--simulate":
            cfg.simulate = True
        elif a == "--backend":
            cfg.backend = next_arg(a)
        elif a == "--show-statistics":
            cfg.show_statistics = True
        elif a == "--show-mappings":
            cfg.show_mappings = True
        elif a == "--show-bad-mappings":
            cfg.show_bad_mappings = True
        elif a == "--show-choose-tries":
            cfg.show_choose_tries = True
        elif a == "--show-utilization":
            cfg.show_utilization = True
        elif a == "--show-utilization-all":
            cfg.show_utilization_all = True
        elif a == "--reweight-item":
            name = next_arg(a)
            w = float(next_arg(a))
            reweights.append((name, w))
        elif do_build and i + 2 < len(args) + 1:
            # build layer triple: name alg size
            lname = a
            alg = next_arg("layer alg")
            size = int(next_arg("layer size"))
            if alg not in _ALGS:
                print(f"unknown bucket alg {alg!r}", file=sys.stderr)
                return 1
            layers.append((lname, alg, size))
        else:
            print(f"unrecognized argument {a!r}", file=sys.stderr)
            return 1
        i += 1

    if decompilefn:
        m = _read_map(decompilefn)
        _write(outfn, decompile(m))
        return 0
    if compilefn:
        m = _read_map(compilefn)  # parse = validate
        _write_map(outfn or "crushmap", m)
        return 0
    if do_build:
        if not num_osds or not layers:
            print("--build requires --num_osds and layers", file=sys.stderr)
            return 1
        m = build_map(num_osds, layers)
        if outfn:
            _write_map(outfn, m)
        else:
            print_tree(m)
        return 0

    if infn is None:
        print("no input map (-i), nothing to do", file=sys.stderr)
        return 1
    m = _read_map(infn)

    changed = False
    by_name = {v: k for k, v in m.item_names.items()}
    for name, w in reweights:
        item = by_name.get(name)
        if item is None:
            print(f"unknown item {name!r}", file=sys.stderr)
            return 1
        m.adjust_item_weight(item, int(w * 0x10000))
        m.build_class_shadow_trees()
        changed = True

    if explain_x is not None:
        return run_explain(m, cfg, explain_x)
    if do_divergence:
        return run_divergence(m, cfg, against_fn)
    if do_tree:
        print_tree(m)
    if do_test:
        CrushTester(m, cfg, out=sys.stdout).test()
    if changed and outfn:
        _write_map(outfn, m)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
