"""fleet — drive N clusters through one stacked dispatch per epoch.

    python -m ceph_tpu.cli.fleet run [--spec FLEETSPEC] [--epochs N]
        [--checkpoint PATH] [--resume] [--stop-after N] [--json]
    python -m ceph_tpu.cli.fleet pareto [--spec FLEETSPEC] ...
    python -m ceph_tpu.cli.fleet digest [--spec FLEETSPEC] ...

`--spec` is the fleet sweep grammar (see `ceph_tpu.fleet.spec`):
semicolon-separated `base=<scenario>`, `axis=key:v1|v2|...`
(cross-product), `clusters=N`, `cluster=i:k=v,...` overrides, and
`backend=jax|ref`.

`run` prints the fleet summary (aggregate rate, steady-compile
contract, per-member digests) — or, with `--json`, the machine-readable
record on one line.  `pareto` prints the non-dominated front as a
triage table (front members first, dominated points with the front
index that beats them).  `digest` prints one line per member:
`<index> <digest>` — the solo-equivalence witnesses.

Exit status: 0 clean, 1 when any member booked an invariant violation.

Crash safety: with `--checkpoint`, the WHOLE stack flushes atomically
every `CEPH_TPU_FLEET_CHECKPOINT_EVERY` fleet epochs; `--resume`
refuses a fleet whose cluster count, order, or any single member's
pinned spec differs from the checkpoint (per-cluster diff in the
error).
"""

from __future__ import annotations

import argparse
import json
import sys

from ceph_tpu.fleet import FleetSim, parse_fleet, triage_table

DEFAULT_SPEC = ("base=epochs=12,hosts=4,osds_per_host=3,racks=2,"
                "pgs=32,ec=2+1,ec_pgs=16,chunk=256,balance_every=0,"
                "spotcheck_every=0,checkpoint_every=0,recovery=queue,"
                "max_backfills=4,recovery_mbps=200,osd_mbps=400;"
                "axis=seed:1|2;axis=correlated:0|1")


def _parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.cli.fleet",
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument("cmd", choices=("run", "pareto", "digest"))
    ap.add_argument("--spec", default=DEFAULT_SPEC,
                    help="fleet sweep-grammar string "
                         "(ceph_tpu.fleet.spec)")
    ap.add_argument("--epochs", type=int, default=None,
                    help="override the fleet epoch count (default: "
                         "the longest member scenario)")
    ap.add_argument("--checkpoint", default=None,
                    help="atomic whole-stack state file")
    ap.add_argument("--resume", action="store_true",
                    help="continue from --checkpoint's last state")
    ap.add_argument("--stop-after", type=int, default=None,
                    help="stop after this fleet epoch (checkpoint + "
                         "exit; the resume test's controlled "
                         "interrupt)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable record as one "
                         "JSON line")
    return ap


def main(argv: list[str] | None = None) -> int:
    args = _parser().parse_args(argv)
    if args.resume and not args.checkpoint:
        print("--resume needs --checkpoint", file=sys.stderr)
        return 2
    members = parse_fleet(args.spec)
    fleet = FleetSim(members, checkpoint=args.checkpoint,
                     resume=args.resume)
    fleet.warm()
    out = fleet.run(epochs=args.epochs, stop_after=args.stop_after)
    violations = sum(m["invariant_violations"] for m in out["members"])
    if args.cmd == "digest":
        if args.json:
            print(json.dumps({m["index"]: m["digest"]
                              for m in out["members"]}))
        else:
            for m in out["members"]:
                print(f"{m['index']} {m['digest']}")
        return 1 if violations else 0
    if args.cmd == "pareto":
        pts = fleet.points()
        if args.json:
            print(json.dumps(out["pareto"]))
        else:
            print(triage_table(pts))
        return 1 if violations else 0
    if args.json:
        print(json.dumps(out))
        return 1 if violations else 0
    t = out["trace_once"]
    print(f"clusters        {out['clusters']} "
          f"({'stacked' if out['stacked'] else 'solo-stepped'}, "
          f"balancer {out['balancer_backend']})")
    print(f"fleet epochs    {out['fleet_epochs']} "
          f"({out['cluster_epochs']} cluster-epochs)")
    print(f"rate            {out['cluster_epochs_per_sec']} "
          f"cluster-epochs/s")
    print(f"trace-once      {t['structural_epochs']} structural / "
          f"{t['steady_epochs']} steady epochs, "
          f"{t['steady_compiles']} steady compile(s)")
    front = out["pareto"]
    print(f"pareto          front {front['front_size']} / dominated "
          f"{len(front['dominated'])}")
    for m in out["members"]:
        p = m["pareto"]
        print(f"  [{m['index']:>3}] {m['backend']:<3} "
              f"epochs {m['epochs']:>4} "
              f"cyrs/h {p['cluster_years_per_hour']:<8g} "
              f"qps {p['served_qps']:<8g} "
              f"pg_lost {int(p['pg_lost'])} "
              f"digest {m['digest'][:12]}")
    if out.get("resumed_from") is not None:
        print(f"resumed from    fleet epoch {out['resumed_from']}")
    print(f"invariants      {violations} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
