"""psim — toy placement simulator (reference src/tools/psim.cc:1-117):
build a simple map, map a grid of objects across pools, histogram the
placements, print per-OSD counts."""

from __future__ import annotations

import sys

import numpy as np

from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.osdmap import build_simple
from ceph_tpu.osd.pipeline_jax import PoolMapper
from ceph_tpu.osd.types import PgId


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    n_osd = int(args[0]) if args else 40
    m = build_simple(n_osd, pg_bits=4, pgp_bits=4)
    count = np.zeros(m.max_osd, np.int64)
    first = np.zeros(m.max_osd, np.int64)
    for pid in sorted(m.pools):
        up, upp, acting, actp = PoolMapper(m, pid).map_all()
        for row in acting:
            osds = [o for o in row if o != ITEM_NONE]
            for o in osds:
                count[o] += 1
            if osds:
                first[osds[0]] += 1
    for i in range(m.max_osd):
        print(f"osd.{i}\t{count[i]}\t{first[i]}")
    print(f"avg {count.mean():.2f} stddev {count.std():.2f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
