"""ceph_erasure_code_benchmark equivalent.

Flag-compatible with the reference benchmark CLI (reference
src/test/erasure-code/ceph_erasure_code_benchmark.cc:40-144 setup,
:156-186 encode loop, :251-317 decode loop, :202-249 exhaustive erasures):

    ec_benchmark --plugin jerasure --workload encode|decode \
        --size TOTAL_BYTES --iterations N \
        --parameter k=4 --parameter m=2 [--parameter technique=...] \
        [--erasures E | --erasures-generation exhaustive] [--verbose]

Prints "<seconds>\t<KiB processed>" like the reference.
The heavy math runs on the configured backend engine ("backend" parameter:
numpy | jax — jax = the TPU bit-plane MXU path).
"""

from __future__ import annotations

import itertools
import sys
import time

import numpy as np

from ceph_tpu.ec import create_erasure_code


def _parse(argv: list[str]) -> dict:
    opts = {
        "plugin": "jerasure",
        "workload": "encode",
        "size": 1 << 20,
        "iterations": 1,
        "erasures": 1,
        "erasures_generation": "random",
        "erased": [],
        "parameters": {},
        "verbose": False,
    }
    i = 0
    while i < len(argv):
        a = argv[i]

        def nxt() -> str:
            nonlocal i
            i += 1
            if i >= len(argv):
                print(f"missing argument for {a}", file=sys.stderr)
                raise SystemExit(1)
            return argv[i]

        if a in ("-p", "--plugin"):
            opts["plugin"] = nxt()
        elif a in ("-w", "--workload"):
            opts["workload"] = nxt()
        elif a in ("-s", "--size"):
            opts["size"] = int(nxt())
        elif a in ("-i", "--iterations"):
            opts["iterations"] = int(nxt())
        elif a in ("-e", "--erasures"):
            opts["erasures"] = int(nxt())
        elif a in ("-N", "--erased"):
            opts["erased"].append(int(nxt()))
        elif a in ("-E", "--erasures-generation"):
            opts["erasures_generation"] = nxt()
        elif a in ("-P", "--parameter"):
            k, _, v = nxt().partition("=")
            opts["parameters"][k] = v
        elif a in ("-v", "--verbose"):
            opts["verbose"] = True
        else:
            print(f"unrecognized argument {a!r}", file=sys.stderr)
            raise SystemExit(1)
        i += 1
    return opts


def run(opts: dict, out=None) -> float:
    out = out or sys.stdout
    profile = dict(opts["parameters"])
    profile["plugin"] = opts["plugin"]
    code = create_erasure_code(profile)
    k, m = code.k, code.m
    n = k + m
    size = opts["size"]
    rng = np.random.default_rng(0xEC)
    data = rng.integers(0, 256, size, dtype=np.int64).astype(np.uint8)
    want_all = set(range(n))

    if opts["workload"] == "encode":
        t0 = time.perf_counter()
        for _ in range(opts["iterations"]):
            code.encode(want_all, data)
        dt = time.perf_counter() - t0
        kib = size * opts["iterations"] / 1024
    else:
        encoded = code.encode(want_all, data)
        if opts["erased"]:
            patterns = [tuple(opts["erased"])]
        elif opts["erasures_generation"] == "exhaustive":
            patterns = list(
                itertools.combinations(range(n), opts["erasures"])
            )
        else:
            patterns = [
                tuple(
                    rng.choice(n, opts["erasures"], replace=False).tolist()
                )
                for _ in range(opts["iterations"])
            ]
        t0 = time.perf_counter()
        kib = 0.0
        for it in range(opts["iterations"]):
            pat = patterns[it % len(patterns)]
            have = {
                i: c for i, c in encoded.items() if i not in pat
            }
            got = code.decode(set(range(k)), dict(have))
            assert all(i in got for i in range(k))
            kib += size / 1024
        dt = time.perf_counter() - t0

    print(f"{dt:g}\t{kib:.0f}", file=out)
    return dt


def main(argv: list[str] | None = None) -> int:
    opts = _parse(list(sys.argv[1:] if argv is None else argv))
    run(opts)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
