"""serve — drive the placement serving daemon from the shell.

    python -m ceph_tpu.cli.serve run [--pgs N] [--osds N] [--seconds S]
        [--clients N] [--checkpoint PATH] [--resume] [--json]
    python -m ceph_tpu.cli.serve chaos [--scenario SPEC] [--epochs N]
        [--clients N] [--checkpoint PATH] [--resume] [--json]
    python -m ceph_tpu.cli.serve query <pool>.<seed> | --object NAME
        [--pgs N] [--osds N] [--checkpoint PATH] [--resume]

`run` serves a synthetic cluster (or a checkpointed epoch with
`--resume`) under a seeded self-load for `--seconds`, printing a QPS /
p50 / p99 / shed summary.  `chaos` points the PR 10 lifetime engine's
epoch churn at the live service while the load runs — the
client-visible tail under control-plane churn is the headline.

Crash safety: with `--checkpoint`, every accepted epoch flushes
`{epoch, map blob}` atomically (`runtime.Checkpoint`).  After a kill
(e.g. `CEPH_TPU_FAULTS="serve_dispatch.40=exit:9"` dies at the 40th
micro-batch), re-running with `--resume` restores the same epoch and
prints `resumed_epoch` + `sample_digest` — the digest must equal the
host oracle's over the checkpointed map, which is how the restart test
proves the daemon answers identically.

Exit status: 0 clean, 1 when any submitted query was dropped (no
reply) — shed/expired replies are answers, drops are the one
forbidden outcome.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _build(pgs: int, osds: int):
    from ceph_tpu.osd.osdmap import build_hierarchical
    from ceph_tpu.osd.types import PgPool, PoolType

    per_host = 4
    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=pgs, pgp_num=pgs)
    return build_hierarchical(
        max(1, osds // per_host), per_host,
        n_rack=max(1, osds // per_host // 4), pool=pool,
    )


def _service(args):
    from ceph_tpu.serve import PlacementService, ServeConfig

    cfg = ServeConfig.from_env()
    if args.resume:
        return PlacementService(config=cfg, checkpoint=args.checkpoint,
                                resume=True)
    return PlacementService(_build(args.pgs, args.osds), config=cfg,
                            checkpoint=args.checkpoint)


def _run(args) -> int:
    import threading

    from ceph_tpu.serve.chaos import _Client, _pct

    svc = _service(args)
    stop = threading.Event()
    clients = [_Client(svc, i, args.batch, stop)
               for i in range(args.clients)]
    t0 = time.perf_counter()
    for c in clients:
        c.thread.start()
    time.sleep(args.seconds)
    stop.set()
    for c in clients:
        c.thread.join(timeout=30)
    wall = time.perf_counter() - t0
    lat = [v for c in clients for v in c.latencies]
    submitted = sum(c.submitted for c in clients)
    replied = sum(c.replied for c in clients)
    ok = sum(c.by_status.get("ok", 0) for c in clients)
    st = svc.status()
    out = {
        "epoch": svc.epoch,
        "wall_s": round(wall, 3),
        "submitted": submitted,
        "dropped": submitted - replied,
        "answered_ok": ok,
        "qps": round(ok / wall, 1) if wall else 0.0,
        "p50_s": _pct(lat, 50),
        "p99_s": _pct(lat, 99),
        "queries_shed": st["queries_shed"],
        "queries_expired": st["queries_expired"],
        "degraded_answered": st["degraded_answered"],
        "sample_digest": svc.sample_digest(),
    }
    if svc.resumed_from is not None:
        out["resumed_epoch"] = svc.resumed_from
    svc.close()
    return _emit(args, out)


def _chaos(args) -> int:
    from ceph_tpu.serve.chaos import run_chaos

    out = run_chaos(
        scenario=args.scenario, epochs=args.epochs,
        checkpoint=args.checkpoint, resume=args.resume,
        clients=args.clients, client_batch=args.batch,
    )
    return _emit(args, out)


def _query(args) -> int:
    svc = _service(args)
    try:
        if args.object is not None:
            pool = args.pool if args.pool >= 0 else \
                sorted(svc._active.m.pools)[0]
            r = svc.lookup_object(pool, args.object)
            what = f"object {args.object!r} pool {pool}"
        else:
            if not args.pgid or "." not in args.pgid:
                print("query needs <pool>.<seed> or --object NAME",
                      file=sys.stderr)
                return 2
            p, _, s = args.pgid.partition(".")
            r = svc.lookup(int(p), int(s, 0))  # "1.42" or "1.0x2a"
            what = f"pg {args.pgid}"
        out = {
            "query": what, "status": r.status, "epoch": r.epoch,
            "source": r.source,
        }
        if r.ok:
            out["up"] = [int(o) for o in r.up[0]]
            out["up_primary"] = int(r.up_primary[0])
            out["acting"] = [int(o) for o in r.acting[0]]
            out["acting_primary"] = int(r.acting_primary[0])
        print(json.dumps(out, indent=None if args.json else 1))
        return 0 if r.ok else 1
    finally:
        svc.close()


def _emit(args, out: dict) -> int:
    if args.json:
        print(json.dumps(out))
    else:
        for k in ("resumed_epoch", "sample_digest", "epochs",
                  "final_epoch", "epoch", "wall_s", "submitted",
                  "dropped", "answered_ok", "qps", "p50_s", "p99_s",
                  "swaps_ok", "swaps_rejected", "swap_stall_p99_s",
                  "queries_shed", "queries_expired",
                  "degraded_answered", "sim_digest"):
            if k in out and out[k] is not None:
                print(f"{k:20} {out[k]}")
        slo = out.get("slo")
        if slo:
            print(f"{'slo':20} burning={slo['burning']} "
                  f"raised={slo['burns_raised']} "
                  f"cleared={slo['burns_cleared']} "
                  f"burn_minutes={slo['burn_minutes']} "
                  f"breaches={slo['breaches']}/{slo['samples']}")
        h = out.get("health")
        if h:
            codes = ",".join(sorted(h.get("checks") or ())) or "-"
            print(f"{'health':20} {h['status']} ({codes})")
    return 1 if out.get("dropped") else 0


def _parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.cli.serve",
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument("cmd", choices=("run", "chaos", "query"))
    ap.add_argument("pgid", nargs="?", default=None,
                    help="query: <pool>.<seed>")
    ap.add_argument("--pgs", type=int, default=1024,
                    help="synthetic cluster pg_num (default 1024)")
    ap.add_argument("--osds", type=int, default=32,
                    help="synthetic cluster OSD count (default 32)")
    ap.add_argument("--seconds", type=float, default=5.0,
                    help="run: load duration (default 5)")
    ap.add_argument("--clients", type=int, default=2,
                    help="seeded client-load threads (default 2)")
    ap.add_argument("--batch", type=int, default=256,
                    help="queries per client request (default 256)")
    ap.add_argument("--scenario", default=None,
                    help="chaos: lifetime Scenario overrides "
                         "(comma-separated key=value)")
    ap.add_argument("--epochs", type=int, default=None,
                    help="chaos: churn epochs (default: scenario's)")
    ap.add_argument("--object", default=None,
                    help="query: object name instead of <pool>.<seed>")
    ap.add_argument("--pool", type=int, default=-1,
                    help="query --object: pool id (default: first)")
    ap.add_argument("--checkpoint", default=None,
                    help="atomic epoch+map state file for crash-safe "
                         "serving")
    ap.add_argument("--resume", action="store_true",
                    help="restore the checkpointed epoch and serve it")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable record")
    return ap


def main(argv: list[str] | None = None) -> int:
    args = _parser().parse_args(argv)
    if args.resume and not args.checkpoint:
        print("--resume needs --checkpoint", file=sys.stderr)
        return 2
    if args.cmd == "run":
        return _run(args)
    if args.cmd == "chaos":
        return _chaos(args)
    return _query(args)


if __name__ == "__main__":
    raise SystemExit(main())
