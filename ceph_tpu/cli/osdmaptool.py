"""osdmaptool — create/inspect/test osdmaps, batched on TPU.

Drop-in CLI for the reference tool (reference src/tools/osdmaptool.cc):
same flags, same messages, same exit codes, same output formats — pinned
by replaying the reference's own cram transcripts
(src/test/cli/osdmaptool/*.t) in tests/test_cram_osdmaptool.py.

    osdmaptool mapfile --createsimple N [--pg-bits B] [--pgp-bits B]
                        [--with-default-pool] [--clobber]
    osdmaptool mapfile --create-from-conf -c ceph.conf
    osdmaptool mapfile --print | --dump FMT | --tree[=plain|json-pretty]
    osdmaptool mapfile --test-map-pgs[-dump[-all]] [--pool P]
    osdmaptool mapfile --test-map-pg <pgid> / --test-map-object <name>
    osdmaptool mapfile --mark-up-in / --mark-out N / --mark-up N
    osdmaptool mapfile --adjust-crush-weight osd:weight[,..] [--save]
    osdmaptool mapfile --upmap out [--upmap-deviation D] [--upmap-max N]
                        [--upmap-pool name] [--save]
    osdmaptool mapfile --upmap-cleanup [f]
    osdmaptool mapfile --export-crush f / --import-crush f
    osdmaptool mapfile --apply-incremental incfile   (extension: applies
                        binary OSDMap::Incremental epoch deltas in order)

Map files are the reference binary wire format (JSON also read, see
ceph_tpu.osd.io).  The per-PG mapping loop runs as one batched XLA call
per pool (the ParallelPGMapper analogue; reference loop
src/tools/osdmaptool.cc:630-755).
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

import numpy as np

from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.osdmap import OSDMap, build_simple
from ceph_tpu.osd.types import PgId

ME = "osdmaptool"

USAGE = """ usage: [--print] <mapfilename>
   --create-from-conf      creates an osd map with default configurations
   --createsimple <numosd> [--clobber] [--pg-bits <bitsperosd>] [--pgp-bits <bits>] creates a relatively generic OSD map with <numosd> devices
   --pgp-bits <bits>       pgp_num map attribute will be shifted by <bits>
   --pg-bits <bits>        pg_num map attribute will be shifted by <bits>
   --clobber               allows osdmaptool to overwrite <mapfilename> if it already exists
   --export-crush <file>   write osdmap's crush map to <file>
   --import-crush <file>   replace osdmap's crush map with <file>
   --health                dump health checks
   --test-map-pgs [--pool <poolid>] [--pg_num <pg_num>] [--range-first <first> --range-last <last>] map all pgs
   --test-map-pgs-dump [--pool <poolid>] [--range-first <first> --range-last <last>] map all pgs
   --test-map-pgs-dump-all [--pool <poolid>] [--range-first <first> --range-last <last>] map all pgs to osds
   --mark-up-in            mark osds up and in (but do not persist)
   --mark-out <osdid>      mark an osd as out (but do not persist)
   --mark-up <osdid>       mark an osd as up (but do not persist)
   --mark-in <osdid>       mark an osd as in (but do not persist)
   --with-default-pool     include default pool when creating map
   --clear-temp            clear pg_temp and primary_temp
   --clean-temps           clean pg_temps
   --test-random           do random placements
   --test-map-pg <pgid>    map a pgid to osds
   --test-map-object <objectname> [--pool <poolid>] map an object to osds
   --upmap-cleanup <file>  clean up pg_upmap[_items] entries, writing
                           commands to <file> [default: - for stdout]
   --upmap <file>          calculate pg upmap entries to balance pg layout
                           writing commands to <file> [default: - for stdout]
   --upmap-max <max-count> set max upmap entries to calculate [default: 10]
   --upmap-deviation <max-deviation>
                           max deviation from target [default: 5]
   --upmap-pool <poolname> restrict upmap balancing to 1 or more pools
   --upmap-active          Act like an active balancer, keep applying changes until balanced
   --dump <format>         displays the map in plain text when <format> is 'plain', 'json' if specified format is not supported
   --tree                  displays a tree of the map
   --test-crush [--range-first <first> --range-last <last>] map pgs to acting osds
   --adjust-crush-weight <osdid:weight>[,<osdid:weight>,<...>] change <osdid> CRUSH <weight> (but do not persist)
   --save                  write modified osdmap with upmap or crush-adjust changes
"""


def _vec(v) -> str:
    return "[" + ",".join(str(int(o)) for o in v) + "]"


def _g(v: float) -> str:
    return f"{v:g}"


def _crush_weightf_map(m: OSDMap) -> dict[int, float]:
    """One pass over the (non-shadow) buckets: device -> crush weight."""
    shadows = {
        sid
        for per in m.crush.class_bucket.values()
        for sid in per.values()
    }
    out: dict[int, float] = {}
    for bid, b in m.crush.buckets.items():
        if bid in shadows:
            continue
        for it, w in zip(b.items, b.weights):
            if it >= 0 and it not in out:
                out[it] = w / 0x10000
    return out


def _map_pool(m: OSDMap, pool_id: int, backend: str):
    """-> (acting[N,W], acting_primary[N], up[N,W], up_primary[N]) numpy."""
    pool = m.pools[pool_id]
    if backend == "jax":
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        up, upp, acting, actp = PoolMapper(m, pool_id).map_all()
        return acting, actp, up, upp
    n = pool.pg_num
    W = pool.size
    up = np.full((n, W), ITEM_NONE, np.int32)
    upp = np.full(n, -1, np.int32)
    acting = np.full((n, W), ITEM_NONE, np.int32)
    actp = np.full(n, -1, np.int32)
    for ps in range(n):
        u, up_pr, a, a_pr = m.pg_to_up_acting_osds(PgId(pool_id, ps))
        up[ps, : len(u)] = u
        acting[ps, : len(a)] = a
        upp[ps] = up_pr
        actp[ps] = a_pr
    return acting, actp, up, upp


def map_health(m: OSDMap, backend: str = "jax") -> dict:
    """Evaluate the obs/health checks against a loaded map: OSD
    exists/up state plus per-PG live-mapping occupancy vs the pool's
    size (degraded), min_size (at risk) and zero (unmapped)."""
    from ceph_tpu.obs import health

    exists = down = 0
    for o in range(m.max_osd):
        if m.exists(o):
            exists += 1
            if m.is_down(o):
                down += 1
    degraded = unmapped = at_risk = 0
    for pid in sorted(m.pools):
        pool = m.pools[pid]
        acting, _actp, _up, _upp = _map_pool(m, pid, backend)
        for ps in range(pool.pg_num):
            live = sum(1 for o in acting[ps]
                       if o != ITEM_NONE and m.is_up(o))
            if live == 0:
                unmapped += 1
                continue
            if live < pool.size:
                degraded += 1
            if live < pool.min_size:
                at_risk += 1
    health.reset()  # this tool reports THIS map, not process history
    health.evaluate(osds_down=down, osd_count=exists, degraded=degraded,
                    unmapped=unmapped, at_risk=at_risk)
    return health.dump()


def test_map_pgs(
    m: OSDMap,
    only_pool: int = -1,
    dump: str | None = None,
    backend: str = "jax",
    out=None,
) -> None:
    """reference src/tools/osdmaptool.cc:630-755 output format."""
    out = out or sys.stdout
    n = m.max_osd
    count = np.zeros(n, np.int64)
    first_count = np.zeros(n, np.int64)
    primary_count = np.zeros(n, np.int64)
    sizes: dict[int, int] = {}
    for pid in sorted(m.pools):
        if only_pool != -1 and pid != only_pool:
            continue
        pool = m.pools[pid]
        print(f"pool {pid} pg_num {pool.pg_num}", file=out)
        acting, actp, up, upp = _map_pool(m, pid, backend)
        for ps in range(pool.pg_num):
            osds = [o for o in acting[ps] if o != ITEM_NONE]
            sizes[len(osds)] = sizes.get(len(osds), 0) + 1
            for o in osds:
                count[o] += 1
            if osds:
                first_count[osds[0]] += 1
            if actp[ps] >= 0:
                primary_count[actp[ps]] += 1
            if dump == "dump":
                print(
                    f"{pid}.{ps:x}\t{_vec(osds)}\t{actp[ps]}", file=out
                )
            elif dump == "dump_all":
                raw = [o for o in up[ps] if o != ITEM_NONE]
                print(
                    f"{pid}.{ps:x} raw ({_vec(raw)}, p{upp[ps]}) "
                    f"up ({_vec(raw)}, p{upp[ps]}) "
                    f"acting ({_vec(osds)}, p{actp[ps]})",
                    file=out,
                )

    total = 0
    n_in = 0
    min_osd = max_osd = -1
    cwf = _crush_weightf_map(m)
    print("#osd\tcount\tfirst\tprimary\tc wt\twt", file=out)
    for i in range(n):
        if not m.is_in(i):
            continue
        cw = cwf.get(i, 0.0)
        if cw <= 0:
            continue
        n_in += 1
        print(
            f"osd.{i}\t{count[i]}\t{first_count[i]}\t{primary_count[i]}"
            f"\t{_g(cw)}\t{_g(m.get_weightf(i))}",
            file=out,
        )
        total += count[i]
        if count[i] and (min_osd < 0 or count[i] < count[min_osd]):
            min_osd = i
        if count[i] and (max_osd < 0 or count[i] > count[max_osd]):
            max_osd = i
    avg = total // n_in if n_in else 0
    dev = 0.0
    for i in range(n):
        if not m.is_in(i) or cwf.get(i, 0.0) <= 0:
            continue
        dev += float((avg - count[i]) ** 2)
    dev = math.sqrt(dev / n_in) if n_in else 0.0
    edev = (
        math.sqrt(total / n_in * (1.0 - 1.0 / n_in)) if n_in else 0.0
    )
    print(f" in {n_in}", file=out)
    print(
        f" avg {avg} stddev {_g(dev)} ({_g(dev / avg) if avg else 'nan'}x) "
        f"(expected {_g(edev)} {_g(edev / avg) if avg else 'nan'}x))",
        file=out,
    )
    if min_osd >= 0:
        print(f" min osd.{min_osd} {count[min_osd]}", file=out)
    if max_osd >= 0:
        print(f" max osd.{max_osd} {count[max_osd]}", file=out)
    for sz in sorted(sizes):
        print(f"size {sz}\t{sizes[sz]}", file=out)


class _Args:
    """ceph_argparse-alike: --opt val / --opt=val, '-' == '_'."""

    def __init__(self, argv: list[str]):
        self.argv = argv
        self.i = 0

    def done(self) -> bool:
        return self.i >= len(self.argv)

    def peek(self) -> str:
        return self.argv[self.i]

    @staticmethod
    def _norm(a: str) -> str:
        return a.replace("-", "_")

    def flag(self, *names: str) -> bool:
        a = self.peek().split("=", 1)[0]
        if self._norm(a) in {self._norm(n) for n in names}:
            self.i += 1
            return True
        return False

    def witharg(self, *names: str) -> str | None:
        """Returns the value, or None if flag doesn't match.  A matching
        flag with a missing value errors like ceph_argparse."""
        a = self.argv[self.i]
        head, eq, tail = a.partition("=")
        if self._norm(head) not in {self._norm(n) for n in names}:
            return None
        if eq:
            self.i += 1
            return tail
        if self.i + 1 >= len(self.argv):
            print(f"Option {head} requires an argument.", file=sys.stderr)
            print("", file=sys.stderr)
            raise SystemExit(1)
        self.i += 2
        return self.argv[self.i - 1]

    def withint(self, *names: str) -> int | None:
        v = self.witharg(*names)
        if v is None:
            return None
        try:
            return int(v)
        except ValueError:
            print(f"The option value '{v}' is invalid", file=sys.stderr)
            raise SystemExit(1)


def _now_utime() -> tuple[int, int]:
    t = time.time()
    return int(t), int((t % 1) * 1e9)


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        print(f"{ME}: -h or --help for usage", file=sys.stderr)
        return 1
    if "-h" in args or "--help" in args:
        print(USAGE, end="", file=sys.stderr)
        return 1

    createsimple = False
    num_osd = 0
    create_from_conf = False
    createpool = False
    conf_file = None
    pg_bits, pgp_bits = 6, 6
    do_print = False
    print_format: str | None = None
    tree = False
    tree_format: str | None = None
    mark_up_in = False
    marked_out = -1
    marked_up = -1
    clobber = False
    test_map_pgs_mode: str | None = None
    pool = -1
    pg_num = -1
    backend = "jax"
    upmap = False
    upmap_cleanup = False
    upmap_file = "-"
    upmap_deviation = 5
    upmap_max = 10
    upmap_pools: list[str] = []
    save = False
    export_crush = None
    import_crush = None
    test_map_pg = None
    test_map_object = None
    adjust_crush_weight = None
    incrementals: list[str] = []
    fn = None
    default_pool_size: int | None = None
    aggressive = True  # osd_calc_pg_upmaps_aggressively default
    marked_in = -1
    do_health = False

    p = _Args(args)
    while not p.done():
        if p.flag("--print", "-p"):
            do_print = True
        elif (v := p.witharg("--dump")) is not None:
            do_print = True
            if v and v != "plain":
                print_format = v
        elif p.peek().split("=", 1)[0] == "--tree":
            a = p.peek()
            p.i += 1
            tree = True
            if "=" in a and a.split("=", 1)[1] not in ("", "plain"):
                tree_format = a.split("=", 1)[1]
        elif (v := p.withint("--createsimple")) is not None:
            createsimple = True
            num_osd = v
        elif p.flag("--create-from-conf"):
            create_from_conf = True
        elif p.flag("--with-default-pool"):
            createpool = True
        elif (v := p.witharg("-c", "--conf")) is not None:
            conf_file = v
        elif (v := p.withint("--pg-bits", "--osd-pg-bits")) is not None:
            pg_bits = v
        elif (v := p.withint("--pgp-bits", "--osd-pgp-bits")) is not None:
            pgp_bits = v
        elif p.flag("--clobber"):
            clobber = True
        elif p.flag("--mark-up-in"):
            mark_up_in = True
        elif (v := p.withint("--mark-out")) is not None:
            marked_out = v
        elif (v := p.withint("--mark-up")) is not None:
            marked_up = v
        elif (v := p.withint("--mark-in")) is not None:
            marked_in = v
        elif p.flag("--health"):
            do_health = True
        elif p.flag("--test-map-pgs"):
            test_map_pgs_mode = "stats"
        elif p.flag("--test-map-pgs-dump"):
            test_map_pgs_mode = "dump"
        elif p.flag("--test-map-pgs-dump-all"):
            test_map_pgs_mode = "dump_all"
        elif (v := p.witharg("--test-map-pg")) is not None:
            test_map_pg = v
        elif (v := p.witharg("--test-map-object")) is not None:
            test_map_object = v
        elif (v := p.withint("--pool")) is not None:
            pool = v
        elif (v := p.withint("--pg-num")) is not None:
            pg_num = v
        elif (v := p.witharg("--backend")) is not None:
            backend = v
        elif (v := p.witharg("--upmap")) is not None:
            upmap = True
            upmap_cleanup = True
            upmap_file = v
        elif (v := p.witharg("--upmap-cleanup")) is not None:
            upmap_cleanup = True
            upmap_file = v
        elif (v := p.withint("--upmap-max")) is not None:
            upmap_max = v
        elif (v := p.withint("--upmap-deviation")) is not None:
            upmap_deviation = v
        elif (v := p.witharg("--upmap-pool")) is not None:
            upmap_pools.append(v)
        elif p.flag("--save"):
            save = True
        elif (v := p.witharg("--export-crush")) is not None:
            export_crush = v
        elif (v := p.witharg("--import-crush")) is not None:
            import_crush = v
        elif (v := p.witharg("--adjust-crush-weight")) is not None:
            adjust_crush_weight = v
        elif (v := p.witharg("--apply-incremental")) is not None:
            incrementals.append(v)
        elif p.peek().split("=", 1)[0].replace("-", "_") == \
                "__osd_calc_pg_upmaps_aggressively":
            a = p.peek()
            p.i += 1
            if "=" in a:
                aggressive = a.split("=", 1)[1].lower() not in (
                    "false", "0", "no")
            else:
                aggressive = True
        elif (v := p.withint("--osd-pool-default-size")) is not None:
            default_pool_size = v
        elif not p.peek().startswith("-"):
            if fn is None:
                fn = p.peek()
                p.i += 1
            else:
                print("too many arguments", file=sys.stderr)
                print(USAGE, end="", file=sys.stderr)
                return 1
        else:
            p.i += 1  # unrecognized: ceph_argparse skips it

    if (upmap or upmap_cleanup) and upmap_deviation < 1:
        print("upmap-deviation must be >= 1", file=sys.stderr)
        print(USAGE, end="", file=sys.stderr)
        return 1

    if fn is None:
        print(f"{ME}: must specify osdmap filename", file=sys.stderr)
        print(USAGE, end="", file=sys.stderr)
        return 1

    print(f"{ME}: osdmap file '{fn}'", file=sys.stderr)

    m: OSDMap | None = None
    modified = False
    write_out = False

    if not createsimple and not create_from_conf and not clobber:
        if not os.path.exists(fn):
            print(
                f"{ME}: couldn't open {fn}: can't open {fn}: "
                "(2) No such file or directory",
                file=sys.stderr,
            )
            return 255
        from ceph_tpu.osd.io import load_osdmap

        try:
            m = load_osdmap(fn)
        except Exception:
            print(f"{ME}: error decoding osdmap '{fn}'", file=sys.stderr)
            return 255
    elif (createsimple or create_from_conf) and not clobber \
            and os.path.exists(fn):
        print(f"{ME}: {fn} exists, --clobber to overwrite", file=sys.stderr)
        return 255
    else:
        m = OSDMap()  # --clobber without create: fresh empty map

    if createsimple or create_from_conf:
        if createsimple:
            if num_osd < 1:
                print(f"{ME}: osd count must be > 0", file=sys.stderr)
                return 1
            m = build_simple(
                num_osd, pg_bits, pgp_bits, default_pool=createpool,
                mark_up_in=False,
            )
            m.epoch = 0
        else:
            from ceph_tpu.osd.conf import build_from_conf

            if not conf_file:
                print(f"{ME}: --create-from-conf requires -c", file=sys.stderr)
                return 1
            m = build_from_conf(
                conf_file, pg_bits, pgp_bits, default_pool=createpool,
            )
        if createpool and 1 in m.pools and default_pool_size is not None:
            m.pools[1].size = default_pool_size
            m.pools[1].min_size = default_pool_size - default_pool_size // 2
        now = _now_utime()
        m.wire = {"pools": {}, "created": now, "modified": now,
                  "fsid": bytes(16)}
        modified = True
    assert m is not None

    for incfile in incrementals:
        from ceph_tpu.osd.incremental import (
            apply_incremental,
            decode_incremental,
        )

        with open(incfile, "rb") as f:
            inc = decode_incremental(f.read())
        m = apply_incremental(m, inc)
        print(
            f"{ME}: applied incremental epoch {inc.epoch} from {incfile}",
            file=sys.stderr,
        )
        write_out = True  # the delta already carries the new epoch

    if mark_up_in:
        print("marking all OSDs up and in")
        cwf = _crush_weightf_map(m)
        for o in range(m.max_osd):
            m.osd_state[o] |= 0b11  # EXISTS|UP (set_weight sets EXISTS)
            m.osd_weight[o] = 0x10000
            if cwf.get(o, 0.0) == 0.0:
                m.crush.adjust_item_weight(o, 0x10000)

    if 0 <= marked_out < m.max_osd:
        print(f"marking OSD@{marked_out} as out")
        m.osd_state[marked_out] |= 0b11
        m.osd_weight[marked_out] = 0

    if 0 <= marked_up < m.max_osd:
        print(f"marking OSD@{marked_up} as up")
        m.osd_state[marked_up] |= 0b10  # UP only (osdmaptool.cc:373-377)

    if 0 <= marked_in < m.max_osd:
        print(f"marking OSD@{marked_in} as up")  # reference message quirk
        m.osd_weight[marked_in] = 0x10000
        m.osd_state[marked_in] |= 0b01  # set_weight marks EXISTS

    if adjust_crush_weight:
        from ceph_tpu.osd.incremental import Incremental, apply_incremental

        for spec in adjust_crush_weight.split(","):
            if ":" not in spec:
                print(f"{ME}: use ':' as separator of osd id and its weight",
                      file=sys.stderr)
                print(USAGE, end="", file=sys.stderr)
                return 1
            osd_s, w_s = spec.split(":", 1)
            osd_id, new_weight = int(osd_s), float(w_s)
            m.crush.adjust_item_weight(osd_id, int(new_weight * 0x10000))
            print(f"Adjusted osd.{osd_id} CRUSH weight to {_g(new_weight)}")
            if save:
                m = apply_incremental(m, Incremental(epoch=m.epoch + 1))
                modified = True

    upmap_fd = None
    if upmap or upmap_cleanup:
        if upmap_file != "-":
            upmap_fd = open(upmap_file, "w")
            print(f"writing upmap command output to: {upmap_file}")

    def emit_upmap(lines: list[str]):
        out = upmap_fd or sys.stdout
        for ln in lines:
            print(ln, file=out)

    if upmap_cleanup:
        print("checking for upmap cleanups")
        cancelled, remapped = m.clean_pg_upmaps()
        lines = [f"ceph osd rm-pg-upmap-items {pg}" for pg in cancelled]
        for pg, items in remapped.items():
            pairs = " ".join(f"{f} {t}" for f, t in items)
            lines.append(f"ceph osd pg-upmap-items {pg} {pairs}")
        if lines:  # clean_pg_upmaps already mutated m
            emit_upmap(lines)
            m.epoch += 1

    if upmap:
        from ceph_tpu.balancer import calc_pg_upmaps

        print(f"upmap, max-count {upmap_max}, max deviation "
              f"{upmap_deviation}")
        pool_ids: list[int] = []
        if upmap_pools:
            for name in upmap_pools:
                found = [pid for pid, n in m.pool_name.items() if n == name]
                if not found:
                    print(f" pool {name} does not exist", file=sys.stderr)
                    return 1
                pool_ids += found
            print(f" limiting to pools {upmap_pools} ({pool_ids})")
        else:
            pool_ids = sorted(m.pools)
        if not pool_ids:
            print("No pools available")
        else:
            print("pools " + " ".join(
                m.pool_name.get(i, str(i)) for i in pool_ids
            ) + " ")
            total_did = 0
            left = upmap_max
            lines: list[str] = []
            saved_items = {pg: list(v) for pg, v in m.pg_upmap_items.items()}
            for pid in pool_ids:
                res = calc_pg_upmaps(
                    m,
                    max_deviation=upmap_deviation,
                    max_iter=left,
                    only_pools={pid},
                    use_tpu=(backend == "jax"),
                    aggressive=aggressive,
                )
                for pg in sorted(res.old_pg_upmap_items):
                    lines.append(f"ceph osd rm-pg-upmap-items {pg}")
                for pg, items in sorted(res.new_pg_upmap_items.items()):
                    pairs = " ".join(f"{f} {t}" for f, t in items)
                    lines.append(f"ceph osd pg-upmap-items {pg} {pairs}")
                total_did += res.num_changed
                left -= res.num_changed
                if left <= 0:
                    break
            print(f"prepared {total_did}/{upmap_max} changes")
            if total_did > 0:
                emit_upmap(lines)
                if save:
                    m.epoch += 1
                    modified = True
                else:
                    # reference only applies pending_inc when saving
                    m.pg_upmap_items = saved_items
            else:
                print("Unable to find further optimization, or distribution"
                      " is already perfect")

    if upmap_fd is not None:
        upmap_fd.close()

    if import_crush:
        from ceph_tpu.crush.codec import encode_crushmap
        from ceph_tpu.osd.incremental import Incremental, apply_incremental
        from ceph_tpu.osd.io import load_crush_text

        from ceph_tpu.crush.codec import looks_like_crushmap

        with open(import_crush, "rb") as f:
            raw = f.read()
        cw = load_crush_text(import_crush)
        if cw.max_devices > m.max_osd:
            print(f"{ME}: crushmap max_devices {cw.max_devices} > "
                  f"osdmap max_osd {m.max_osd}", file=sys.stderr)
            return 1
        blob = raw if looks_like_crushmap(raw) else encode_crushmap(cw)
        inc = Incremental(epoch=m.epoch + 1)
        inc.crush = blob
        m = apply_incremental(m, inc)
        print(f"{ME}: imported {len(blob)} byte crush map from "
              f"{import_crush}")
        modified = True

    if export_crush:
        from ceph_tpu.crush.codec import encode_crushmap

        with open(export_crush, "wb") as f:
            f.write(encode_crushmap(m.crush))
        print(f"{ME}: exported crush map to {export_crush}")

    if test_map_object:
        from ceph_tpu.core.intmath import pg_mask_for, stable_mod
        from ceph_tpu.core.rjenkins import str_hash_rjenkins

        if pool == -1:
            print(f"{ME}: assuming pool 1 (use --pool to override)")
            pool = 1
        if pool not in m.pools:
            print(f"There is no pool {pool}", file=sys.stderr)
            return 1
        pp = m.pools[pool]
        ps = str_hash_rjenkins(test_map_object.encode())
        seed = int(stable_mod(ps, pp.pg_num, pg_mask_for(pp.pg_num)))
        pgid = PgId(pool, seed)
        _, _, acting, _ = m.pg_to_up_acting_osds(pgid)
        print(f" object '{test_map_object}' -> {pgid} -> {_vec(acting)}")

    if test_map_pg:
        try:
            pg = PgId.parse(test_map_pg)
        except Exception:
            print(f"{ME}: failed to parse pg '{test_map_pg}",
                  file=sys.stderr)
            print(USAGE, end="", file=sys.stderr)
            return 1
        print(f" parsed '{test_map_pg}' -> {pg}")
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg)
        print(
            f"{pg} raw ({_vec(up)}, p{upp}) "
            f"up ({_vec(up)}, p{upp}) acting ({_vec(acting)}, p{actp})"
        )

    if test_map_pgs_mode:
        if pool != -1 and pool not in m.pools:
            print(f"There is no pool {pool}", file=sys.stderr)
            return 1
        if pg_num > 0 and pool in m.pools:
            m.pools[pool].pg_num = pg_num
        test_map_pgs(
            m,
            only_pool=pool,
            dump=None if test_map_pgs_mode == "stats" else test_map_pgs_mode,
            backend=backend,
        )

    health_rc = 0
    if do_health:
        h = map_health(m, backend=backend)
        print(json.dumps(h, indent=1, sort_keys=True))
        if h["status"] != "HEALTH_OK":
            health_rc = 1

    no_action = not (
        do_print or tree or modified or write_out or export_crush
        or import_crush or test_map_pg or test_map_object
        or test_map_pgs_mode or adjust_crush_weight or upmap
        or upmap_cleanup or do_health
    )
    if no_action:
        print(f"{ME}: no action specified?", file=sys.stderr)
        print(USAGE, end="", file=sys.stderr)
        return 1

    if modified:
        m.epoch += 1

    if do_print:
        from ceph_tpu.osd.print import print_osdmap

        if print_format:
            from ceph_tpu.osd.io import osdmap_to_dict

            d = osdmap_to_dict(m)
            d.pop("crush", None)
            print(json.dumps(d, indent=4))
        else:
            print_osdmap(m, sys.stdout)

    if tree:
        from ceph_tpu.osd.print import print_tree_plain, tree_json

        if tree_format:
            print(json.dumps(tree_json(m), indent=4))
            print()
        else:
            print_tree_plain(m, sys.stdout)

    if modified or write_out:
        from ceph_tpu.osd.io import save_osdmap

        if "modified" in getattr(m, "wire", {}) and (createsimple
                                                     or create_from_conf):
            m.wire["modified"] = _now_utime()
        print(f"{ME}: writing epoch {m.epoch} to {fn}")
        save_osdmap(m, fn)
    return health_rc


if __name__ == "__main__":
    raise SystemExit(main())
