"""osdmaptool — create/inspect/test osdmaps, batched on TPU.

Covers the reference tool's standalone surface (reference
src/tools/osdmaptool.cc:41-68 usage):

    osdmaptool mapfile --createsimple N [--pg-bits B] [--pgp-bits B]
    osdmaptool mapfile --create-from-conf-like  (hierarchical: --num-hosts)
    osdmaptool mapfile --print
    osdmaptool mapfile --test-map-pgs [--pool P] [--backend jax|ref]
    osdmaptool mapfile --test-map-pgs-dump
    osdmaptool mapfile --test-map-pgs-dump-all
    osdmaptool mapfile --test-map-pg <pgid>
    osdmaptool mapfile --mark-up-in
    osdmaptool mapfile --upmap out.txt [--upmap-deviation D]
                        [--upmap-max N] [--upmap-pool name]
    osdmaptool mapfile --upmap-cleanup
    osdmaptool mapfile --export-crush f / --import-crush f
    osdmaptool mapfile --apply-incremental incfile   (repeatable; applies
                        binary OSDMap::Incremental epoch deltas in order)

Map files are the framework's JSON osdmap format (ceph_tpu.osd.io); the
stats output mirrors the reference's --test-map-pgs table
(reference src/tools/osdmaptool.cc:630-755).

The per-PG mapping loop runs as one batched XLA call per pool
(`--backend jax`, default) or through the host oracle (`--backend ref`).
"""

from __future__ import annotations

import math
import sys
import time

import numpy as np

from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.io import (
    load_crush_text,
    load_osdmap,
    osdmap_to_dict,
    save_crush_text,
    save_osdmap,
)
from ceph_tpu.osd.osdmap import OSDMap, build_simple
from ceph_tpu.osd.types import PgId


def _vec(v) -> str:
    return "[" + ",".join(str(int(o)) for o in v) + "]"


def _crush_weightf_map(m: OSDMap) -> dict[int, float]:
    """One pass over the (non-shadow) buckets: device -> crush weight."""
    shadows = {
        sid
        for per in m.crush.class_bucket.values()
        for sid in per.values()
    }
    out: dict[int, float] = {}
    for bid, b in m.crush.buckets.items():
        if bid in shadows:
            continue
        for it, w in zip(b.items, b.weights):
            if it >= 0 and it not in out:
                out[it] = w / 0x10000
    return out


def _map_pool(m: OSDMap, pool_id: int, backend: str):
    """-> (acting[N,W], acting_primary[N], up[N,W], up_primary[N]) numpy."""
    pool = m.pools[pool_id]
    if backend == "jax":
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        up, upp, acting, actp = PoolMapper(m, pool_id).map_all()
        return acting, actp, up, upp
    n = pool.pg_num
    W = pool.size
    up = np.full((n, W), ITEM_NONE, np.int32)
    upp = np.full(n, -1, np.int32)
    acting = np.full((n, W), ITEM_NONE, np.int32)
    actp = np.full(n, -1, np.int32)
    for ps in range(n):
        u, up_pr, a, a_pr = m.pg_to_up_acting_osds(PgId(pool_id, ps))
        up[ps, : len(u)] = u
        acting[ps, : len(a)] = a
        upp[ps] = up_pr
        actp[ps] = a_pr
    return acting, actp, up, upp


def test_map_pgs(
    m: OSDMap,
    only_pool: int = -1,
    dump: str | None = None,
    backend: str = "jax",
    out=None,
) -> None:
    out = out or sys.stdout
    n = m.max_osd
    count = np.zeros(n, np.int64)
    first_count = np.zeros(n, np.int64)
    primary_count = np.zeros(n, np.int64)
    sizes: dict[int, int] = {}
    for pid in sorted(m.pools):
        if only_pool != -1 and pid != only_pool:
            continue
        pool = m.pools[pid]
        print(f"pool {pid} pg_num {pool.pg_num}", file=out)
        acting, actp, up, upp = _map_pool(m, pid, backend)
        for ps in range(pool.pg_num):
            osds = [o for o in acting[ps] if o != ITEM_NONE]
            sizes[len(osds)] = sizes.get(len(osds), 0) + 1
            for o in osds:
                count[o] += 1
            if osds:
                first_count[osds[0]] += 1
            if actp[ps] >= 0:
                primary_count[actp[ps]] += 1
            if dump == "dump":
                print(
                    f"{pid}.{ps:x}\t{_vec(osds)}\t{actp[ps]}", file=out
                )
            elif dump == "dump_all":
                raw = [o for o in up[ps] if o != ITEM_NONE]
                print(
                    f"{pid}.{ps:x} raw ({_vec(raw)}, p{upp[ps]}) "
                    f"up ({_vec(raw)}, p{upp[ps]}) "
                    f"acting ({_vec(osds)}, p{actp[ps]})",
                    file=out,
                )

    total = 0
    n_in = 0
    min_osd = max_osd = -1
    cwf = _crush_weightf_map(m)
    print("#osd\tcount\tfirst\tprimary\tc wt\twt", file=out)
    for i in range(n):
        if not m.is_in(i):
            continue
        cw = cwf.get(i, 0.0)
        if cw <= 0:
            continue
        n_in += 1
        print(
            f"osd.{i}\t{count[i]}\t{first_count[i]}\t{primary_count[i]}"
            f"\t{cw:g}\t{m.get_weightf(i):g}",
            file=out,
        )
        total += count[i]
        if count[i] and (min_osd < 0 or count[i] < count[min_osd]):
            min_osd = i
        if count[i] and (max_osd < 0 or count[i] > count[max_osd]):
            max_osd = i
    avg = total // n_in if n_in else 0
    dev = 0.0
    for i in range(n):
        if not m.is_in(i) or cwf.get(i, 0.0) <= 0:
            continue
        dev += float((avg - count[i]) ** 2)
    dev = math.sqrt(dev / n_in) if n_in else 0.0
    edev = (
        math.sqrt(total / n_in * (1.0 - 1.0 / n_in)) if n_in else 0.0
    )
    print(f" in {n_in}", file=out)
    if avg:
        print(
            f" avg {avg} stddev {dev:g} ({dev / avg:g}x) "
            f"(expected {edev:g} {edev / avg:g}x))",
            file=out,
        )
    if min_osd >= 0:
        print(f" min osd.{min_osd} {count[min_osd]}", file=out)
    if max_osd >= 0:
        print(f" max osd.{max_osd} {count[max_osd]}", file=out)
    for sz in sorted(sizes):
        print(f"size {sz}\t{sizes[sz]}", file=out)


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        print("usage: osdmaptool <mapfile> [options]", file=sys.stderr)
        return 1
    mapfile = None
    createsimple = 0
    pg_bits, pgp_bits = 6, 6
    do_print = False
    mark_up_in = False
    clobber = False
    test_mode: str | None = None
    test_pool = -1
    backend = "jax"
    upmap_file = None
    upmap_deviation = 5
    upmap_max = 10
    upmap_pools: set[int] = set()
    upmap_cleanup = False
    export_crush = None
    import_crush = None
    test_map_pg = None
    incrementals: list[str] = []

    i = 0

    def next_arg(what: str) -> str:
        nonlocal i
        i += 1
        if i >= len(args):
            print(f"missing argument for {what}", file=sys.stderr)
            raise SystemExit(1)
        return args[i]

    pending_pool_names: list[str] = []
    while i < len(args):
        a = args[i]
        if a == "--createsimple":
            createsimple = int(next_arg(a))
        elif a == "--pg-bits" or a == "--pg_bits":
            pg_bits = int(next_arg(a))
        elif a == "--pgp-bits" or a == "--pgp_bits":
            pgp_bits = int(next_arg(a))
        elif a == "--clobber":
            clobber = True
        elif a == "--print":
            do_print = True
        elif a == "--mark-up-in":
            mark_up_in = True
        elif a == "--test-map-pgs":
            test_mode = "stats"
        elif a == "--test-map-pgs-dump":
            test_mode = "dump"
        elif a == "--test-map-pgs-dump-all":
            test_mode = "dump_all"
        elif a == "--test-map-pg":
            test_map_pg = next_arg(a)
        elif a == "--pool":
            test_pool = int(next_arg(a))
        elif a == "--backend":
            backend = next_arg(a)
        elif a == "--upmap":
            upmap_file = next_arg(a)
        elif a == "--upmap-deviation":
            upmap_deviation = int(next_arg(a))
        elif a == "--upmap-max":
            upmap_max = int(next_arg(a))
        elif a == "--upmap-pool":
            pending_pool_names.append(next_arg(a))
        elif a == "--upmap-cleanup":
            upmap_cleanup = True
        elif a == "--export-crush":
            export_crush = next_arg(a)
        elif a == "--import-crush":
            import_crush = next_arg(a)
        elif a == "--apply-incremental":
            incrementals.append(next_arg(a))
        elif mapfile is None and not a.startswith("-"):
            mapfile = a
        else:
            print(f"unrecognized argument {a!r}", file=sys.stderr)
            return 1
        i += 1

    if mapfile is None:
        print("no mapfile given", file=sys.stderr)
        return 1

    if createsimple:
        import os

        if os.path.exists(mapfile) and not clobber:
            print(
                f"osdmaptool: {mapfile} exists, --clobber to overwrite",
                file=sys.stderr,
            )
            return 1
        m = build_simple(createsimple, pg_bits, pgp_bits)
        save_osdmap(m, mapfile)
        print(
            f"osdmaptool: writing epoch {m.epoch} to {mapfile}",
            file=sys.stderr,
        )
        return 0

    m = load_osdmap(mapfile)
    dirty = False

    for incfile in incrementals:
        from ceph_tpu.osd.incremental import (
            apply_incremental,
            decode_incremental,
        )

        with open(incfile, "rb") as f:
            inc = decode_incremental(f.read())
        m = apply_incremental(m, inc)
        print(
            f"osdmaptool: applied incremental epoch {inc.epoch} from "
            f"{incfile}",
            file=sys.stderr,
        )
        dirty = True

    if import_crush:
        m.crush = load_crush_text(import_crush)
        dirty = True
        print(
            f"osdmaptool: imported crushmap from {import_crush}",
            file=sys.stderr,
        )
    if mark_up_in:
        for o in range(m.max_osd):
            m.mark_up_in(o)
        dirty = True
    if export_crush:
        save_crush_text(m.crush, export_crush)
        print(
            f"osdmaptool: exported crush map to {export_crush}",
            file=sys.stderr,
        )

    for name in pending_pool_names:
        found = [p for p, n in m.pool_name.items() if n == name]
        if not found:
            print(f"osdmaptool: pool {name!r} not found", file=sys.stderr)
            return 1
        upmap_pools.update(found)

    if upmap_cleanup:
        cancelled, remapped = m.clean_pg_upmaps()
        for pg in cancelled:
            print(f"ceph osd rm-pg-upmap-items {pg}")
        for pg, items in remapped.items():
            pairs = " ".join(f"{f} {t}" for f, t in items)
            print(f"ceph osd pg-upmap-items {pg} {pairs}")
        if cancelled or remapped:
            dirty = True

    if upmap_file:
        from ceph_tpu.balancer import calc_pg_upmaps

        lines = []
        if upmap_file:
            t0 = time.perf_counter()
            res = calc_pg_upmaps(
                m,
                max_deviation=upmap_deviation,
                max_iter=upmap_max,
                only_pools=upmap_pools or None,
                use_tpu=(backend == "jax"),
            )
            dt = time.perf_counter() - t0
            print(f"Time elapsed {dt:g} secs", file=sys.stderr)
            for pg in sorted(res.old_pg_upmap_items):
                lines.append(f"ceph osd rm-pg-upmap-items {pg}")
            for pg, items in sorted(res.new_pg_upmap_items.items()):
                pairs = " ".join(f"{f} {t}" for f, t in items)
                lines.append(f"ceph osd pg-upmap-items {pg} {pairs}")
            print(f"upmap, max-count {upmap_max}, max deviation "
                  f"{upmap_deviation}", file=sys.stderr)
            if res.num_changed == 0:
                print("Unable to find further optimization, or distribution"
                      " is already perfect", file=sys.stderr)
            with open(upmap_file, "w") as f:
                f.write("\n".join(lines) + ("\n" if lines else ""))
            dirty = True

    if test_map_pg:
        pg = PgId.parse(test_map_pg)
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg)
        print(
            f"parsed '{pg}' -> {pg}\n{pg} raw ({_vec(up)}, p{upp}) "
            f"up ({_vec(up)}, p{upp}) acting ({_vec(acting)}, p{actp})"
        )
    if test_mode:
        test_map_pgs(
            m,
            only_pool=test_pool,
            dump=None if test_mode == "stats" else test_mode,
            backend=backend,
        )
    if do_print:
        import json

        d = osdmap_to_dict(m)
        d.pop("crush")
        print(json.dumps(d, indent=1))

    if dirty:
        save_osdmap(m, mapfile)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
