"""balancer — the `ceph balancer` command surface for this port.

The reference drives balancing through mgr commands (`ceph balancer
status|eval|optimize|show|execute`, reference pybind/mgr/balancer/
module.py:130-330 COMMANDS).  Same verbs here, over a map file or a
synthetic cluster:

    python -m ceph_tpu.cli.balancer -i map.bin status
    python -m ceph_tpu.cli.balancer -i map.bin eval [--pool P] [-v]
    python -m ceph_tpu.cli.balancer -i map.bin optimize myplan \
        [--mode upmap|crush-compat] [--pool P] [--plan-out plan.inc] \
        [--execute -o out.bin]
    python -m ceph_tpu.cli.balancer show plan.inc
    python -m ceph_tpu.cli.balancer -i map.bin execute plan.inc -o out.bin

A plan artifact IS an OSDMap Incremental (osd.incremental wire format):
`optimize --plan-out` writes one, `show` decodes one, `execute` applies
one — the same epoch-delta currency the reference mon speaks.

Map sources: `-i` reads a binary osdmap (osd.codec); `--synthetic
H,P,PGS[,skew]` builds an H-host x P-osd cluster with PGS placement
groups (skewed weights so there is something to balance — the
TestOSDMap upmap fixtures' shape).  `--mapper host|jax` selects the
scoring mapper (default jax: the batched pipeline).
"""

from __future__ import annotations

import json
import sys

from ceph_tpu.mgr import Balancer, MappingState, synthetic_pg_stats
from ceph_tpu.osd.codec import decode_osdmap, encode_osdmap
from ceph_tpu.osd.incremental import (
    decode_incremental,
    encode_incremental,
)
from ceph_tpu.osd.osdmap import OSDMap, build_hierarchical
from ceph_tpu.osd.types import PgPool, PoolType


def build_synthetic(spec: str) -> OSDMap:
    """H,P,PGS[,skew] -> unbalanced hierarchical cluster."""
    parts = spec.split(",")
    n_host, per, pg_num = int(parts[0]), int(parts[1]), int(parts[2])
    skew = float(parts[3]) if len(parts) > 3 else 2.0

    def wf(osd: int) -> int:
        # alternate-host weight skew: plenty of deviation to optimize
        return int(0x10000 * (skew if (osd // per) % 2 else 1.0))

    pool = PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=pg_num, pgp_num=pg_num,
    )
    return build_hierarchical(n_host, per, pool=pool, weight_fn=wf)


def _load_map(infn: str | None, synthetic: str | None) -> OSDMap:
    if synthetic:
        return build_synthetic(synthetic)
    if infn is None:
        print("no input map: -i <osdmap> or --synthetic H,P,PGS",
              file=sys.stderr)
        raise SystemExit(1)
    with open(infn, "rb") as f:
        return decode_osdmap(f.read())


def _state(m: OSDMap, mapper: str) -> MappingState:
    return MappingState(
        m, synthetic_pg_stats(m), desc="current cluster", mapper=mapper
    )


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    infn = None
    outfn = None
    synthetic = None
    mapper = "jax"
    mode = None
    pools: list[str] = []
    plan_out = None
    verbose = False
    do_execute = False
    cmd: list[str] = []

    i = 0

    def next_arg(what: str) -> str:
        nonlocal i
        i += 1
        if i >= len(args):
            print(f"missing argument for {what}", file=sys.stderr)
            raise SystemExit(1)
        return args[i]

    while i < len(args):
        a = args[i]
        if a in ("-i", "--infn"):
            infn = next_arg(a)
        elif a in ("-o", "--outfn"):
            outfn = next_arg(a)
        elif a == "--synthetic":
            synthetic = next_arg(a)
        elif a == "--mapper":
            mapper = next_arg(a)
        elif a == "--mode":
            mode = next_arg(a)
        elif a == "--pool":
            pools.append(next_arg(a))
        elif a == "--plan-out":
            plan_out = next_arg(a)
        elif a in ("-v", "--verbose"):
            verbose = True
        elif a == "--execute":
            do_execute = True
        elif a.startswith("-") and a not in ("-",):
            print(f"unrecognized option {a!r}", file=sys.stderr)
            return 1
        else:
            cmd.append(a)
        i += 1

    if not cmd:
        print(__doc__, file=sys.stderr)
        return 1
    verb = cmd[0]

    if verb == "show":
        if len(cmd) < 2:
            print("show <planfile>", file=sys.stderr)
            return 1
        with open(cmd[1], "rb") as f:
            inc = decode_incremental(f.read())
        print(f"plan epoch {inc.epoch}")
        for pg in sorted(
            inc.new_pg_upmap_items, key=lambda p: (p.pool, p.seed)
        ):
            pairs = inc.new_pg_upmap_items[pg]
            print(f"ceph osd pg-upmap-items {pg.pool}.{pg.seed:x} "
                  + " ".join(f"{a} {b}" for a, b in pairs))
        for pg in sorted(
            inc.old_pg_upmap_items, key=lambda p: (p.pool, p.seed)
        ):
            print(f"ceph osd rm-pg-upmap-items {pg.pool}.{pg.seed:x}")
        for osd in sorted(inc.new_weight):
            print(f"ceph osd reweight osd.{osd} "
                  f"{inc.new_weight[osd] / 0x10000:.6f}")
        if inc.crush:
            from ceph_tpu.crush.codec import decode_crushmap

            crush = decode_crushmap(inc.crush)
            ca = crush.choose_args.get(-1)
            n = len(ca.weight_sets) if ca else 0
            print(f"new crush map: {len(inc.crush)} bytes, compat "
                  f"weight-set over {n} buckets")
        return 0

    bal = Balancer()
    if mode:
        bal.options["mode"] = mode

    if verb == "status":  # needs no map: options + plan inventory only
        print(json.dumps(bal.status(), indent=2))
        return 0

    m = _load_map(infn, synthetic)

    if verb == "eval":
        pe = bal.eval(_state(m, mapper), pools or None)
        print(pe.show(verbose=verbose))
        return 0

    if verb == "optimize":
        if len(cmd) < 2:
            print("optimize <plan-name>", file=sys.stderr)
            return 1
        ms = _state(m, mapper)
        pe0 = bal.eval(ms, pools or None)
        plan = bal.plan_create(cmd[1], ms, pools or None, mode=mode)
        rc, detail = bal.optimize(plan)
        if rc != 0:
            print(f"optimize failed ({rc}): {detail}", file=sys.stderr)
            return 1
        # crush-compat already scored its accepted state (re-evaluating
        # would recompile the pipeline for nothing); upmap needs one
        pe1 = plan.final_eval or bal.eval(plan.final_state(), pools or None)
        print(plan.show())
        print(f"score {pe0.score:.6f} -> {pe1.score:.6f} "
              f"(mode {plan.mode})")
        if plan_out:
            with open(plan_out, "wb") as f:
                f.write(encode_incremental(plan.finalize_inc()))
            print(f"wrote plan incremental to {plan_out}")
        if do_execute:
            rc, detail = bal.execute(plan, m)
            if rc != 0:
                print(f"execute failed ({rc}): {detail}", file=sys.stderr)
                return 1
            if outfn:
                with open(outfn, "wb") as f:
                    f.write(encode_osdmap(m))
                print(f"wrote epoch {m.epoch} map to {outfn}")
        return 0

    if verb == "execute":
        if len(cmd) < 2:
            print("execute <planfile> [-o outmap]", file=sys.stderr)
            return 1
        from ceph_tpu.osd.incremental import apply_incremental

        with open(cmd[1], "rb") as f:
            inc = decode_incremental(f.read())
        if inc.epoch != m.epoch + 1:
            print(f"plan epoch {inc.epoch} != map epoch {m.epoch}+1 "
                  "(map changed since the plan was computed)",
                  file=sys.stderr)
            return 1
        apply_incremental(m, inc)
        print(f"applied plan: map now epoch {m.epoch}")
        if outfn:
            with open(outfn, "wb") as f:
                f.write(encode_osdmap(m))
            print(f"wrote epoch {m.epoch} map to {outfn}")
        return 0

    print(f"unknown command {verb!r}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
